// sparknet_tpu native data plane: record DB + batch augmenter.
//
// TPU-native equivalents of the reference's native data components:
//  - record DB: the role of Caffe's db abstraction + SparkNet's DB writer
//    (ref: caffe/src/caffe/util/db.cpp, db_lmdb.cpp, db_leveldb.cpp;
//    libccaffe/ccaffe.cpp:51-81 create_db/write_to_db/commit_db_txn) —
//    an append-only log of key/value records with a scanning cursor.
//    LMDB itself is not in this image; the format is deliberately trivial:
//    [u32 klen][u32 vlen][key][val]... with a header carrying a committed
//    record count, so a torn write past the last commit is ignored on open.
//  - augmenter: the role of Caffe's DataTransformer hot loop
//    (ref: caffe/src/caffe/util/data_transformer.cpp:19-119) — uint8 NCHW
//    -> float32 mean-subtract + random-crop + mirror + scale, multithreaded
//    across samples.  This is the path whose JVM incarnation cost the
//    reference ~1.2 s per 256-image batch (ref:
//    src/test/scala/apps/CallbackBenchmarkSpec.scala:3-17).
//
// C ABI only (consumed via ctypes); no exceptions across the boundary.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x534e4442;  // "SNDB"
constexpr uint32_t kVersion = 1;

struct Header {
  uint32_t magic;
  uint32_t version;
  uint64_t committed;  // record count visible to readers
};

struct Record {
  std::string key;
  std::string val;
};

struct Db {
  FILE* f = nullptr;
  bool writable = false;
  uint64_t committed = 0;   // records durable in the header
  uint64_t pending = 0;     // appended since last commit
  std::vector<Record> index;  // populated for read handles
  std::string error;
};

struct Cursor {
  Db* db;
  size_t pos = 0;
};

bool write_header(Db* db) {
  Header h{kMagic, kVersion, db->committed};
  if (fseek(db->f, 0, SEEK_SET) != 0) return false;
  if (fwrite(&h, sizeof(h), 1, db->f) != 1) return false;
  return fflush(db->f) == 0;
}

bool load_index(Db* db) {
  Header h;
  if (fseek(db->f, 0, SEEK_SET) != 0) return false;
  if (fread(&h, sizeof(h), 1, db->f) != 1) return false;
  if (h.magic != kMagic || h.version != kVersion) return false;
  db->committed = h.committed;
  db->index.reserve(h.committed);
  for (uint64_t i = 0; i < h.committed; ++i) {
    uint32_t klen, vlen;
    if (fread(&klen, 4, 1, db->f) != 1 || fread(&vlen, 4, 1, db->f) != 1)
      return false;
    Record r;
    r.key.resize(klen);
    r.val.resize(vlen);
    if (klen && fread(&r.key[0], 1, klen, db->f) != klen) return false;
    if (vlen && fread(&r.val[0], 1, vlen, db->f) != vlen) return false;
    db->index.push_back(std::move(r));
  }
  return true;
}

// splitmix64: per-sample deterministic RNG stream
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- record DB
void* sndb_open(const char* path, int writable) {
  Db* db = new Db;
  db->writable = writable != 0;
  if (writable) {
    db->f = fopen(path, "wb+");
    if (!db->f) { delete db; return nullptr; }
    if (!write_header(db)) { fclose(db->f); delete db; return nullptr; }
    if (fseek(db->f, 0, SEEK_END) != 0) { fclose(db->f); delete db; return nullptr; }
  } else {
    db->f = fopen(path, "rb");
    if (!db->f) { delete db; return nullptr; }
    if (!load_index(db)) { fclose(db->f); delete db; return nullptr; }
  }
  return db;
}

int sndb_put(void* handle, const void* key, int klen, const void* val, int vlen) {
  Db* db = static_cast<Db*>(handle);
  if (!db->writable || klen < 0 || vlen < 0) return -1;
  // Remember the record start so a failed write can rewind — a torn
  // partial record left in the stream would desync every later record
  // when load_index parses sequentially.
  long start = ftell(db->f);
  uint32_t k = static_cast<uint32_t>(klen), v = static_cast<uint32_t>(vlen);
  bool ok = fwrite(&k, 4, 1, db->f) == 1 && fwrite(&v, 4, 1, db->f) == 1 &&
            (k == 0 || fwrite(key, 1, k, db->f) == k) &&
            (v == 0 || fwrite(val, 1, v, db->f) == v);
  if (!ok) {
    if (start >= 0) fseek(db->f, start, SEEK_SET);
    return -1;
  }
  db->pending++;
  return 0;
}

// Durability point (ref: commit_db_txn every 1000 puts,
// preprocessing/CreateDB.scala:13-51): records become reader-visible.
int sndb_commit(void* handle) {
  Db* db = static_cast<Db*>(handle);
  if (!db->writable) return -1;
  if (fflush(db->f) != 0) return -1;
  long end = ftell(db->f);
  db->committed += db->pending;
  db->pending = 0;
  if (!write_header(db)) return -1;
  if (fseek(db->f, end, SEEK_SET) != 0) return -1;
  return 0;
}

long long sndb_count(void* handle) {
  return static_cast<long long>(static_cast<Db*>(handle)->committed);
}

void sndb_close(void* handle) {
  Db* db = static_cast<Db*>(handle);
  if (db->f) fclose(db->f);
  delete db;
}

void* sndb_cursor(void* handle) {
  Db* db = static_cast<Db*>(handle);
  if (db->writable) return nullptr;  // cursors read committed snapshots
  Cursor* c = new Cursor{db, 0};
  return c;
}

// Returns 1 and points key/val at internal storage (valid until the cursor
// advances past end or the db closes); 0 at end.
int sndb_next(void* cursor, const void** key, int* klen,
              const void** val, int* vlen) {
  Cursor* c = static_cast<Cursor*>(cursor);
  if (c->pos >= c->db->index.size()) return 0;
  const Record& r = c->db->index[c->pos++];
  *key = r.key.data();
  *klen = static_cast<int>(r.key.size());
  *val = r.val.data();
  *vlen = static_cast<int>(r.val.size());
  return 1;
}

void sndb_cursor_free(void* cursor) { delete static_cast<Cursor*>(cursor); }

// ---------------------------------------------------------------- augmenter
// in:  uint8 [n, c, h, w]
// out: float32 [n, c, oh, ow] where oh=ow=crop (or h,w if crop==0)
// mean_mode: 0 = none; 1 = per-channel (mean has c floats);
//            2 = full image (mean has c*h*w floats, subtracted pre-crop)
// train: random crop offsets + per-sample mirror; else center crop, no mirror
void snaug_transform(const unsigned char* in, int n, int c, int h, int w,
                     const float* mean, int mean_mode, float scale, int crop,
                     int mirror, int train, unsigned long long seed,
                     float* out, int nthreads) {
  const int oh = crop > 0 ? crop : h;
  const int ow = crop > 0 ? crop : w;
  if (nthreads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    nthreads = hw ? static_cast<int>(hw) : 4;
  }
  if (nthreads > n) nthreads = n > 0 ? n : 1;

  auto work = [=](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      uint64_t r = splitmix64(seed + static_cast<uint64_t>(i));
      int ho = 0, wo = 0;
      bool flip = false;
      if (crop > 0) {
        if (train) {
          ho = static_cast<int>(r % static_cast<uint64_t>(h - crop + 1));
          r = splitmix64(r);
          wo = static_cast<int>(r % static_cast<uint64_t>(w - crop + 1));
          r = splitmix64(r);
        } else {
          ho = (h - crop) / 2;
          wo = (w - crop) / 2;
        }
      }
      if (mirror && train) flip = (r & 1) != 0;

      const unsigned char* src = in + static_cast<size_t>(i) * c * h * w;
      float* dst = out + static_cast<size_t>(i) * c * oh * ow;
      for (int ch = 0; ch < c; ++ch) {
        const unsigned char* sc = src + static_cast<size_t>(ch) * h * w;
        const float* mc =
            mean_mode == 2 ? mean + static_cast<size_t>(ch) * h * w : nullptr;
        const float mv = mean_mode == 1 ? mean[ch] : 0.0f;
        for (int y = 0; y < oh; ++y) {
          const unsigned char* row = sc + static_cast<size_t>(y + ho) * w + wo;
          const float* mrow =
              mc ? mc + static_cast<size_t>(y + ho) * w + wo : nullptr;
          float* drow = dst + (static_cast<size_t>(ch) * oh + y) * ow;
          if (!flip) {
            for (int x = 0; x < ow; ++x) {
              float v = static_cast<float>(row[x]);
              v -= mrow ? mrow[x] : mv;
              drow[x] = v * scale;
            }
          } else {
            for (int x = 0; x < ow; ++x) {
              float v = static_cast<float>(row[ow - 1 - x]);
              v -= mrow ? mrow[ow - 1 - x] : mv;
              drow[x] = v * scale;
            }
          }
        }
      }
    }
  };

  if (nthreads == 1) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int lo = t * chunk;
    int hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto& th : threads) th.join();
}

int snative_abi_version() { return 1; }

}  // extern "C"
